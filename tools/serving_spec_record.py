#!/usr/bin/env python
"""Multi-tenant spec-domain serving sweep: the committed record producer.

The domain-as-data acceptance evidence (ISSUE 13): one AttackService
serving THREE tenants side by side, one per constraint-domain origin —

- ``lcld`` — the hand-written class on the code-derived synthetic schema
  (the CI-reproducible artifact recipe from ``bench.py``),
- ``botnet`` — the committed ``domains/specs/botnet.yaml`` served through
  the config ``spec:`` path (the compiler route a YAML edit rides in on),
- ``phishing`` — the data-only spec domain resolved by registry name
  (no hand-written module anywhere in its request path),

driven through an offered-load sweep (mixed PGD + MoEvA traffic so the
record's ``telemetry.quality`` carries engine-judged samples) and written
to ``SERVING_SPEC_r01.json`` with the full ``telemetry.{cost, quality,
slo, gaps}`` block ``validate_record`` requires of serving records. The
record also embeds the service's ``build.domain_origins`` — the per-tenant
provenance (origin + spec hash) that /healthz exposes for fleet
build-fingerprint admission.

Dataset-free by construction (synthetic schemas + seeded surrogates);
env knobs shrink the sweep: SPEC_SWEEP_LOADS / _REQUESTS / _BUDGET.

    python tools/serving_spec_record.py             # write SERVING_SPEC_r01.json
    python tools/serving_spec_record.py --out -     # print, don't write
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import numpy as np  # noqa: E402


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _save_surrogate(tmp: str, name: str, model, n_features: int):
    from moeva2_ijcai22_replication_tpu.models.io import Surrogate, save_params
    from moeva2_ijcai22_replication_tpu.models.mlp import init_params

    sur = Surrogate(model, init_params(model, n_features, seed=1))
    path = os.path.join(tmp, f"{name}.msgpack")
    save_params(sur, path)
    return path


def _save_scaler(tmp: str, name: str, cons, pool: np.ndarray) -> str:
    """MinMax scaler whose envelope covers data ∪ per-state dynamic
    bounds (bench.py's rule: attacked rows at bound extremes must stay
    inside [0, 1] in scaler space)."""
    import joblib
    from sklearn.preprocessing import MinMaxScaler as SkMinMax

    xl, xu = cons.get_feature_min_max(dynamic_input=pool)
    xl = np.broadcast_to(np.asarray(xl, float), pool.shape)
    xu = np.broadcast_to(np.asarray(xu, float), pool.shape)
    path = os.path.join(tmp, f"{name}_scaler.joblib")
    joblib.dump(SkMinMax().fit(np.vstack([pool, xl, xu])), path)
    return path


def build_tenants(tmp: str) -> tuple[dict, dict]:
    """(service ``domains`` config, per-domain candidate pools) for the
    three-origin tenant mix."""
    from moeva2_ijcai22_replication_tpu.domains import (
        SPEC_DIR,
        SPEC_DOMAINS,
        get_constraints_class,
        spec_domain_dir,
    )
    from moeva2_ijcai22_replication_tpu.domains.ir import compile_spec_path
    from moeva2_ijcai22_replication_tpu.domains.lcld import LcldConstraints
    from moeva2_ijcai22_replication_tpu.domains.synth import (
        synth_botnet,
        synth_botnet_schema,
        synth_lcld,
        synth_lcld_schema,
        synth_phishing,
    )
    from moeva2_ijcai22_replication_tpu.models.mlp import botnet_mlp, lcld_mlp

    domains: dict = {}
    pools: dict = {}

    # lcld: hand-written class, synthetic schema
    lp = synth_lcld_schema(os.path.join(tmp, "lcld"))
    lcons = LcldConstraints(lp["features"], lp["constraints"])
    lpool = synth_lcld(512, lcons.schema, seed=7)
    domains["lcld"] = {
        "project_name": "lcld",
        "norm": 2,
        "paths": {
            "model": _save_surrogate(
                tmp, "lcld", lcld_mlp(), lcons.schema.n_features
            ),
            "features": lp["features"],
            "constraints": lp["constraints"],
            "ml_scaler": _save_scaler(tmp, "lcld", lcons, lpool),
        },
        "system": {"mesh_devices": 0},
    }
    pools["lcld"] = lpool

    # botnet: the committed spec served through the config `spec:` path
    # (feat_idx.pickle rides next to the synthetic features.csv)
    bp = synth_botnet_schema(os.path.join(tmp, "botnet"))
    spec_path = os.path.join(SPEC_DIR, SPEC_DOMAINS["botnet_spec"])
    bcons = compile_spec_path(spec_path, name="botnet_spec")(
        bp["features"], bp["constraints"]
    )
    bpool = synth_botnet(256, bcons.schema, seed=7)
    domains["botnet_spec"] = {
        "project_name": "botnet_spec",
        "spec": spec_path,
        "norm": 2,
        "paths": {
            "model": _save_surrogate(
                tmp, "botnet", botnet_mlp(), bcons.schema.n_features
            ),
            "features": bp["features"],
            "constraints": bp["constraints"],
            "ml_scaler": _save_scaler(tmp, "botnet", bcons, bpool),
        },
        "system": {"mesh_devices": 0},
    }
    pools["botnet_spec"] = bpool

    # phishing: data-only spec domain by registry name (committed package
    # data is the schema source)
    pd = spec_domain_dir("phishing")
    pfeat = os.path.join(pd, "features.csv")
    pconsn = os.path.join(pd, "constraints.csv")
    pcons = get_constraints_class("phishing")(pfeat, pconsn)
    ppool = synth_phishing(512, pcons.schema, seed=7)
    domains["phishing"] = {
        "project_name": "phishing",
        "norm": 2,
        "paths": {
            "model": _save_surrogate(
                tmp, "phishing", lcld_mlp(), pcons.schema.n_features
            ),
            "features": pfeat,
            "constraints": pconsn,
            "ml_scaler": _save_scaler(tmp, "phishing", pcons, ppool),
        },
        "system": {"mesh_devices": 0},
    }
    pools["phishing"] = ppool
    return domains, pools


def run_sweep() -> dict:
    from moeva2_ijcai22_replication_tpu.serving import (
        AttackRequest,
        AttackService,
    )
    from moeva2_ijcai22_replication_tpu.serving.sweep import offered_load_sweep

    loads = [
        float(v)
        for v in os.environ.get("SPEC_SWEEP_LOADS", "8,32,96").split(",")
    ]
    n_requests = int(os.environ.get("SPEC_SWEEP_REQUESTS", 66))
    budget = int(os.environ.get("SPEC_SWEEP_BUDGET", 10))
    buckets = (8, 16, 32)
    names = ["lcld", "botnet_spec", "phishing"]

    with tempfile.TemporaryDirectory(prefix="spec_sweep_") as tmp:
        domains, pools = build_tenants(tmp)
        service = AttackService(
            domains,
            bucket_sizes=buckets,
            max_delay_s=0.01,
            max_queue_rows=4096,
        )

        def make_request(i: int) -> AttackRequest:
            domain = names[i % len(names)]
            pool = pools[domain]
            # every 9th request is MoEvA at a fixed shape (one engine
            # compile per domain, paid in warmup) so telemetry.quality
            # carries engine-judged samples for all three tenants
            if i % 9 == len(names):
                return AttackRequest(
                    domain=domain, x=pool[:8], attack="moeva",
                    eps=0.2, budget=4,
                )
            n = 1 + (i * 7) % 13
            start = (i * 17) % (pool.shape[0] - n)
            return AttackRequest(
                domain=domain,
                x=pool[start : start + n],
                eps=0.2,
                budget=budget,
                loss_evaluation="flip",
            )

        # pay every compile before the measured levels: per tenant one PGD
        # request per bucket size + the fixed-shape MoEvA engine
        t0 = time.perf_counter()
        for domain in names:
            for b in service.menu.sizes:
                service.attack(
                    AttackRequest(
                        domain=domain, x=pools[domain][:b], eps=0.2,
                        budget=budget,
                    ),
                    timeout=600.0,
                )
            service.attack(
                AttackRequest(
                    domain=domain, x=pools[domain][:8], attack="moeva",
                    eps=0.2, budget=4,
                ),
                timeout=600.0,
            )
            log(f"[spec_sweep] warmed {domain} "
                f"({time.perf_counter() - t0:.0f}s elapsed)")
        warmup_s = time.perf_counter() - t0

        record = offered_load_sweep(
            service, make_request, loads, n_requests, timeout_s=600.0
        )
        record["warmup_s"] = round(warmup_s, 2)
        record["budget"] = budget
        record["artifacts"] = "synthetic"
        record["tenants"] = {
            name: service.healthz()["build"]["domain_origins"][name]
            for name in names
        }
        service.close()

    for lv in record["levels"]:
        log(
            f"[spec_sweep] @{lv['offered_rps']:g} rps: "
            f"{lv['throughput_rps']} rps, p50 {lv['p50_ms']} ms, "
            f"p99 {lv['p99_ms']} ms, occupancy {lv['mean_batch_occupancy']}"
        )
    knee = record["telemetry"]["slo"]["knee"]
    log(f"[spec_sweep] knee: {knee['knee_rps']} rps; tenants: "
        + ", ".join(
            f"{k}={v['origin']}" for k, v in record["tenants"].items()
        ))
    return record


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument(
        "--out",
        default=os.path.join(REPO, "SERVING_SPEC_r01.json"),
        help="output path for the committed record ('-' prints to stdout)",
    )
    args = parser.parse_args(argv)
    record = {
        "metric": "spec_multitenant_serving_sweep",
        "producer": "tools/serving_spec_record.py",
        "serving": run_sweep(),
    }
    blob = json.dumps(record, indent=1, sort_keys=False) + "\n"
    if args.out == "-":
        sys.stdout.write(blob)
    else:
        with open(args.out, "w") as fh:
            fh.write(blob)
        log(f"[spec_sweep] wrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
