#!/usr/bin/env python
"""Sharding/transfer lint: machine-check the states-sharding contract.

``attacks/sharding.py`` promises that the attack hot loops shard the
states axis over the mesh with no data-plane communication. Until now
that contract lived in prose; this tool compiles the real attack programs
— PGD, AutoPGD, and the MoEvA init/segment/success-gate — for each
lintable domain on an emulated 8-device CPU mesh (the
``xla_force_host_platform_device_count`` recipe tests/conftest.py uses)
and fails on:

- **float collectives in the hot loop** — an all-gather/all-reduce/
  reduce-scatter/collective-permute moving floating-point payload in the
  ``pgd_attack``/``moeva_segment`` executables means candidate or
  objective DATA crosses devices per iteration/generation. (The SPMD
  partitioner legitimately inserts small u32 RNG-key, pred
  loop-consensus, and s32 index collectives even into embarrassingly
  parallel programs — measured ~4.5 KB/segment at lint shapes; those are
  control-plane, tolerated but byte-bounded by the next rule.)
- **collective bytes over budget** — total estimated collective bytes in
  a hot-loop executable past ``--collective-bytes-limit`` (default
  1 MiB/dispatch: ~200x the measured control-plane traffic, orders of
  magnitude under a population-sized gather at production shapes).
- **implicit host<->device transfers at dispatch** — the run executes
  with ``jax.transfer_guard("disallow")`` scoped around every compiled
  dispatch (the ``observability.ledger.set_dispatch_transfer_guard``
  seam), so an argument that is not already resident on its devices
  raises instead of silently serialising the hot path through the host.
- **unintended full replication** — a program whose states-sharded
  inputs compiled fine but whose largest output came back fully
  replicated (or a multi-device attack program with NOTHING sharded at
  all) multiplies memory and work by the mesh size.

    python tools/shard_lint.py --check        # lint committed domains (tier-1)
    python tools/shard_lint.py --selftest     # verify the lint trips on
                                              # injected violations
    python tools/shard_lint.py --check --json # + machine-readable line

Domains: the code-derived synthetic LCLD schema and the spec-compiled
``phishing`` domain always (both dataset-free); the reference
lcld/botnet schemas when /root/reference exists (skipped, not failed,
otherwise — same convention as tools/oracle_check.py).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_CHILD_MARKER = "_MOEVA2_SHARD_LINT_CHILD"

#: default per-dispatch collective-bytes budget for hot-loop executables.
DEFAULT_COLLECTIVE_BYTES_LIMIT = 1 << 20

#: producers whose executables are linted as the hot loop — the single
#: source is observability.mesh, so the lint and the telemetry.mesh
#: hot-loop classification (bench_diff --mesh's gate) cannot drift.
from moeva2_ijcai22_replication_tpu.observability.mesh import (  # noqa: E402
    HOT_LOOP_PRODUCERS as HOT,
)

#: every attack producer linted for replication (gate/init included — they
#: are per-state programs too, just not per-generation).
ATTACK_PRODUCERS = HOT + ("moeva_init", "moeva_success")


def _ensure_devices(n_devices: int, argv_rest: list[str]) -> bool:
    """True when this process already has the virtual mesh; otherwise
    re-exec into a child with the forced device count (parent env never
    mutated — the tests/conftest.py / __graft_entry__ recipe)."""
    import jax

    if os.environ.get(_CHILD_MARKER):
        jax.config.update("jax_platforms", "cpu")
    if len(jax.devices()) >= n_devices:
        return True
    if os.environ.get(_CHILD_MARKER):
        raise RuntimeError(
            f"virtual-device bootstrap failed: forced {n_devices} devices "
            f"but jax.devices() = {len(jax.devices())}"
        )
    import subprocess

    env = dict(os.environ)
    env[_CHILD_MARKER] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    kept = [
        tok
        for tok in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in tok
    ]
    env["XLA_FLAGS"] = " ".join(
        kept + [f"--xla_force_host_platform_device_count={n_devices}"]
    )
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), *argv_rest],
        env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    sys.exit(proc.returncode)


# ---------------------------------------------------------------------------
# rules (pure functions over ledger entries — unit-testable without compiles)
# ---------------------------------------------------------------------------
def classify_dispatch_error(exc: BaseException) -> str:
    """Rule name for an exception raised under the armed transfer guard:
    only guard trips ("Disallowed ... transfer") are ``host_transfer`` —
    anything else is ``engine_error``, still a lint failure (the attack
    programs must compile and run on the mesh) but labeled honestly so an
    unrelated engine regression does not read as a broken sharding
    contract."""
    text = f"{type(exc).__name__}: {exc}".lower()
    if "transfer" in text and ("disallow" in text or "guard" in text):
        return "host_transfer"
    return "engine_error"



def lint_entry(
    entry,
    *,
    hot=HOT,
    collective_bytes_limit: float = DEFAULT_COLLECTIVE_BYTES_LIMIT,
    expect_sharded: bool = True,
) -> list[dict]:
    """Violations of one ledger entry (attributes of
    ``observability.ledger.LedgerEntry`` or an object with the same
    ``producer``/``devices``/``partitions``/``sharding``/``collectives``
    shape). Single-device entries lint clean by construction."""
    out: list[dict] = []
    if getattr(entry, "devices", 1) <= 1:
        return out
    producer = getattr(entry, "producer", "?")
    key = getattr(entry, "key", "?")
    col = getattr(entry, "collectives", None) or {}
    if producer in hot:
        if col.get("float_count"):
            out.append(
                {
                    "rule": "hot_loop_float_collective",
                    "producer": producer,
                    "key": key,
                    "detail": (
                        f"{col['float_count']} collective(s) moving "
                        f"{col.get('float_bytes', 0):.0f} bytes of "
                        "floating-point payload — candidate/objective data "
                        "crosses devices in the hot loop"
                    ),
                }
            )
        if col.get("bytes", 0.0) > collective_bytes_limit:
            out.append(
                {
                    "rule": "hot_loop_collective_bytes",
                    "producer": producer,
                    "key": key,
                    "detail": (
                        f"collectives move {col.get('bytes', 0.0):.0f} "
                        f"bytes/dispatch > limit {collective_bytes_limit:.0f}"
                    ),
                }
            )
    sharding = getattr(entry, "sharding", None) or {}
    if expect_sharded and producer in ATTACK_PRODUCERS:
        if getattr(entry, "partitions", 1) <= 1:
            out.append(
                {
                    "rule": "fully_replicated_program",
                    "producer": producer,
                    "key": key,
                    "detail": (
                        f"compiled on {entry.devices} devices with NOTHING "
                        "partitioned — the states-sharded placement was "
                        "requested but every array is fully replicated"
                    ),
                }
            )
        else:
            in_sum = sharding.get("in") or {}
            out_sum = sharding.get("out") or {}
            largest_out = out_sum.get("largest") if out_sum else None
            largest_sharded_in = max(
                (
                    r["bytes"]
                    for r in [in_sum.get("largest") or {}]
                    if r.get("sharded")
                ),
                default=in_sum.get("sharded_bytes", 0),
            )
            # the big outputs of a states-sharded program must come back
            # states-sharded: a replicated output as large as the sharded
            # inputs means XLA (or a sharding constraint) materialised the
            # full batch on every device
            if (
                largest_out is not None
                and not largest_out.get("sharded")
                and largest_out.get("bytes", 0)
                >= max(4096, 0.5 * largest_sharded_in)
            ):
                out.append(
                    {
                        "rule": "replicated_large_output",
                        "producer": producer,
                        "key": key,
                        "detail": (
                            f"largest output ({largest_out.get('bytes', 0)} "
                            f"bytes, spec {largest_out.get('spec')}) is "
                            "fully replicated while states-sharded inputs "
                            "were requested"
                        ),
                    }
                )
    return out


def lint_entries(entries, **kw) -> list[dict]:
    out = []
    for e in entries:
        out.extend(lint_entry(e, **kw))
    return out


# ---------------------------------------------------------------------------
# domain lint: compile + dispatch the real attack programs
# ---------------------------------------------------------------------------
def _synth_problem(tmp_dir: str):
    import numpy as np

    from moeva2_ijcai22_replication_tpu.domains.lcld import LcldConstraints
    from moeva2_ijcai22_replication_tpu.domains.synth import (
        synth_lcld,
        synth_lcld_schema,
    )
    from moeva2_ijcai22_replication_tpu.models.io import Surrogate
    from moeva2_ijcai22_replication_tpu.models.mlp import (
        init_params,
        lcld_mlp,
    )
    from moeva2_ijcai22_replication_tpu.models.scalers import fit_minmax

    paths = synth_lcld_schema(tmp_dir)
    cons = LcldConstraints(paths["features"], paths["constraints"])
    x = synth_lcld(16, cons.schema, seed=3)
    model = lcld_mlp()
    sur = Surrogate(model, init_params(model, cons.schema.n_features, seed=1))
    return cons, x, sur, fit_minmax(x.min(0), x.max(0))


def _phishing_problem():
    """The spec-compiled data-only domain (dataset-free: committed
    package data + the constraint-first synthetic sampler) — proves a
    domain with NO hand-written module honours the sharding contract."""
    from moeva2_ijcai22_replication_tpu.domains import (
        get_constraints_class,
        spec_domain_dir,
    )
    from moeva2_ijcai22_replication_tpu.domains.synth import synth_phishing
    from moeva2_ijcai22_replication_tpu.models.io import Surrogate
    from moeva2_ijcai22_replication_tpu.models.mlp import init_params, lcld_mlp
    from moeva2_ijcai22_replication_tpu.models.scalers import fit_minmax

    d = spec_domain_dir("phishing")
    cons = get_constraints_class("phishing")(
        os.path.join(d, "features.csv"), os.path.join(d, "constraints.csv")
    )
    x = synth_phishing(16, cons.schema, seed=3)
    model = lcld_mlp()
    sur = Surrogate(model, init_params(model, cons.schema.n_features, seed=1))
    return cons, x, sur, fit_minmax(x.min(0), x.max(0))


def _reference_problem(domain: str):
    import numpy as np

    from moeva2_ijcai22_replication_tpu.domains import get_constraints_class
    from moeva2_ijcai22_replication_tpu.models.io import Surrogate
    from moeva2_ijcai22_replication_tpu.models.mlp import init_params, lcld_mlp
    from moeva2_ijcai22_replication_tpu.models.scalers import fit_minmax

    base = f"/root/reference/data/{domain}"
    features = f"{base}/features.csv"
    constraints = f"{base}/constraints.csv"
    if not os.path.exists(features):
        return None
    cons = get_constraints_class(domain)(features, constraints)
    cand = f"{base}/x_candidates_common.npy"
    if os.path.exists(cand):
        x = np.load(cand)[:16].astype(np.float64)
    else:
        return None  # no committed candidate set for this schema
    model = lcld_mlp(n_features=cons.schema.n_features) if domain == "lcld" else None
    if model is None:
        from moeva2_ijcai22_replication_tpu.models.mlp import botnet_mlp

        model = botnet_mlp()
    sur = Surrogate(model, init_params(model, cons.schema.n_features, seed=1))
    return cons, x, sur, fit_minmax(x.min(0), x.max(0))


def lint_domain(
    name: str,
    problem,
    mesh,
    *,
    collective_bytes_limit: float = DEFAULT_COLLECTIVE_BYTES_LIMIT,
) -> list[dict]:
    """Compile + dispatch every attack program family for one domain on
    ``mesh`` with the transfer guard armed; returns violations."""
    import numpy as np

    from moeva2_ijcai22_replication_tpu.attacks.moeva import Moeva2
    from moeva2_ijcai22_replication_tpu.attacks.pgd import (
        AutoPGD,
        ConstrainedPGD,
    )
    from moeva2_ijcai22_replication_tpu.observability.ledger import (
        get_ledger,
        set_dispatch_transfer_guard,
    )

    cons, x, sur, scaler = problem
    ledger = get_ledger()
    before = {e.key for e in ledger.entries()}
    violations: list[dict] = []
    prev_guard = set_dispatch_transfer_guard("disallow")
    try:
        # MoEvA: tiny budget; quality_every forces the success-gate program
        # to compile+dispatch so all three executables get linted
        moeva = Moeva2(
            classifier=sur, constraints=cons, ml_scaler=scaler,
            norm=2, n_gen=5, n_pop=8, n_offsprings=4, seed=0,
            archive_size=2, record_quality=True, quality_every=2,
            mesh=mesh,
        )
        try:
            moeva.generate(x, minimize_class=1)
        except Exception as e:
            violations.append(
                {
                    "rule": classify_dispatch_error(e),
                    "producer": "moeva",
                    "domain": name,
                    "detail": f"{type(e).__name__}: {e}",
                }
            )
        xs = np.asarray(scaler.transform(x))
        y = np.ones(len(xs), dtype=np.int64)
        for label, cls in (("pgd", ConstrainedPGD), ("autopgd", AutoPGD)):
            attack = cls(
                classifier=sur, constraints=cons, scaler=scaler,
                eps=0.2, eps_step=0.05, max_iter=4,
                loss_evaluation="constraints+flip", mesh=mesh,
            )
            try:
                attack.generate(xs, y)
            except Exception as e:
                violations.append(
                    {
                        "rule": classify_dispatch_error(e),
                        "producer": label,
                        "domain": name,
                        "detail": f"{type(e).__name__}: {e}",
                    }
                )
    finally:
        set_dispatch_transfer_guard(prev_guard)
    new_entries = [e for e in ledger.entries() if e.key not in before]
    for v in lint_entries(
        new_entries, collective_bytes_limit=collective_bytes_limit
    ):
        violations.append(dict(v, domain=name))
    return violations


def run_lint(
    n_devices: int = 8,
    *,
    collective_bytes_limit: float = DEFAULT_COLLECTIVE_BYTES_LIMIT,
) -> tuple[list[dict], list[str], list[str]]:
    """Lint every available domain; returns (violations, linted, skipped)."""
    import tempfile

    import jax
    import numpy as np
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()[:n_devices]), ("states",))
    violations: list[dict] = []
    linted, skipped = [], []
    with tempfile.TemporaryDirectory() as tmp:
        problems = {
            "lcld_synth": _synth_problem(tmp),
            "phishing": _phishing_problem(),
        }
        for domain in ("botnet",):
            p = _reference_problem(domain)
            if p is None:
                skipped.append(domain)
            else:
                problems[domain] = p
        for name, problem in problems.items():
            violations.extend(
                lint_domain(
                    name,
                    problem,
                    mesh,
                    collective_bytes_limit=collective_bytes_limit,
                )
            )
            linted.append(name)
    return violations, linted, skipped


# ---------------------------------------------------------------------------
# selftest: the lint must FAIL on injected violations
# ---------------------------------------------------------------------------
def injected_collective_violations(mesh) -> list[dict]:
    """Compile a hot-loop-named program with an explicit full all-gather
    of a float population tensor (a replicated sharding constraint forces
    one) — the lint must flag it."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from moeva2_ijcai22_replication_tpu.observability.ledger import (
        CostLedger,
        LedgeredJit,
    )

    led = CostLedger()
    x = jax.device_put(
        jnp.ones((16, 64), jnp.float32), NamedSharding(mesh, P("states"))
    )

    def bad(x):
        # force the full population onto every device: an all-gather in
        # the compiled HLO, exactly what a states-mixing bug looks like
        gathered = jax.lax.with_sharding_constraint(x * 2.0, NamedSharding(mesh, P()))
        return gathered - gathered.mean()

    lj = LedgeredJit(jax.jit(bad), producer="moeva_segment", ledger=led)
    lj(x)
    return lint_entries(led.entries())


def injected_transfer_violation(mesh) -> list[dict]:
    """Dispatch a compiled multi-device program with a host numpy argument
    under the armed transfer guard — the implicit host->device transfer
    at dispatch must raise, which the lint reports as a violation."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from moeva2_ijcai22_replication_tpu.observability.ledger import (
        CostLedger,
        LedgeredJit,
        set_dispatch_transfer_guard,
    )

    led = CostLedger()
    x = jax.device_put(
        jnp.ones((16, 8), jnp.float32), NamedSharding(mesh, P("states"))
    )
    lj = LedgeredJit(jax.jit(lambda x: x + 1), producer="pgd_attack", ledger=led)
    lj(x)  # compile + clean dispatch with resident args
    prev = set_dispatch_transfer_guard("disallow")
    try:
        lj(np.ones((16, 8), np.float32))  # host arg: implicit transfer
    except Exception as e:
        return [
            {
                "rule": classify_dispatch_error(e),
                "producer": "pgd_attack",
                "detail": f"{type(e).__name__}: {e}",
            }
        ]
    finally:
        set_dispatch_transfer_guard(prev)
    return []


def run_selftest(n_devices: int = 8) -> dict:
    """Verify the lint trips on injected violations AND that a clean
    sharded program lints clean. Returns per-check booleans."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from moeva2_ijcai22_replication_tpu.observability.ledger import (
        CostLedger,
        LedgeredJit,
    )

    mesh = Mesh(np.array(jax.devices()[:n_devices]), ("states",))
    col = injected_collective_violations(mesh)
    tra = injected_transfer_violation(mesh)
    led = CostLedger()
    x = jax.device_put(
        jnp.ones((16, 8), jnp.float32), NamedSharding(mesh, P("states"))
    )
    clean_lj = LedgeredJit(
        jax.jit(lambda x: x * 2 + 1), producer="pgd_attack", ledger=led
    )
    clean_lj(x)
    clean = lint_entries(led.entries())
    return {
        "collective_tripped": any(
            v["rule"].startswith("hot_loop") or v["rule"] == "replicated_large_output"
            for v in col
        ),
        "transfer_tripped": any(v["rule"] == "host_transfer" for v in tra),
        "clean_passes": not clean,
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument(
        "--check",
        action="store_true",
        help="lint the committed domains (tier-1 repo-check mode)",
    )
    parser.add_argument(
        "--selftest",
        action="store_true",
        help="verify the lint trips on injected violations",
    )
    parser.add_argument("--devices", type=int, default=8)
    parser.add_argument(
        "--collective-bytes-limit",
        type=float,
        default=DEFAULT_COLLECTIVE_BYTES_LIMIT,
        help="hot-loop collective bytes budget per dispatch "
        f"(default {DEFAULT_COLLECTIVE_BYTES_LIMIT})",
    )
    parser.add_argument(
        "--json", action="store_true", help="machine-readable last line"
    )
    args = parser.parse_args(argv)
    if not args.check and not args.selftest:
        parser.error("pass --check and/or --selftest")

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    _ensure_devices(args.devices, list(argv) if argv is not None else sys.argv[1:])

    rc = 0
    result: dict = {"devices": args.devices}
    if args.selftest:
        st = run_selftest(args.devices)
        result["selftest"] = st
        for check, ok in st.items():
            print(f"shard_lint selftest: {check}: {'ok' if ok else 'FAILED'}")
        if not all(st.values()):
            rc = 1
    if args.check:
        violations, linted, skipped = run_lint(
            args.devices,
            collective_bytes_limit=args.collective_bytes_limit,
        )
        result.update(
            {"violations": violations, "linted": linted, "skipped": skipped}
        )
        print(
            f"shard_lint: linted {linted} on a {args.devices}-device mesh"
            + (f", skipped {skipped} (no reference data)" if skipped else "")
        )
        for v in violations:
            print(
                f"  VIOLATION [{v['rule']}] {v.get('domain', '?')}/"
                f"{v.get('producer', '?')}: {v.get('detail', '')}"
            )
        if violations:
            rc = 1
            print("shard_lint: FAILED — the states-sharding contract is broken")
        else:
            print(
                "shard_lint: ok — zero hot-loop data collectives, no "
                "implicit transfers, no unintended replication"
            )
    if args.json:
        print(json.dumps(dict(result, ok=rc == 0)))
    return rc


if __name__ == "__main__":
    sys.exit(main())
