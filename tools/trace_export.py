#!/usr/bin/env python
"""Render trace JSONL sinks to Chrome/Perfetto trace-event JSON.

The unified tracing subsystem (``moeva2_ijcai22_replication_tpu/observability``)
appends one JSON event per line to the path configured as
``system.trace_log`` (runners/grids) or ``serving.trace_log`` (the HTTP
front). This CLI converts that stream to the trace-event format the
Perfetto UI (https://ui.perfetto.dev) and ``chrome://tracing`` open
directly — one process track per trace id (request/run/batch), "X" slices
for spans, instants for progress events (MoEvA gates), counter tracks for
gauges (writer queue depth).

    python tools/trace_export.py out/trace.jsonl
    python tools/trace_export.py out/trace.jsonl -o trace.perfetto.json

Fleet mode merges N per-replica sinks onto one wall-clock timeline (each
sink's meta line anchors its epoch; ``--offsets`` applies the measured
router<->replica clock offsets the ReplicaManager's healthz handshake
reports as ``clock_offset_s`` in the fleet view):

    python tools/trace_export.py --fleet out/trace_r01.jsonl \
        out/trace_r02.jsonl -o fleet.perfetto.json \
        --offsets '{"r01": 0.0, "r02": -0.0012}'

Labels default to the ``rNN``-style suffix of each filename (the
per-replica templating ``tools/serve.py`` applies); pass ``label=path``
to override.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _label_for(path: str) -> str:
    """Infer a replica label from a sink filename: the trailing
    ``_<label>`` chunk serve.py's per-replica templating appends
    (``trace_r02.jsonl`` -> ``r02``), else the bare stem."""
    stem = os.path.splitext(os.path.basename(path))[0]
    m = re.search(r"_([A-Za-z0-9-]+)$", stem)
    return m.group(1) if m else stem


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "paths",
        nargs="+",
        help="trace JSONL file(s); with --fleet, each may be "
        "'label=path' to name its replica track explicitly",
    )
    parser.add_argument(
        "-o",
        "--out",
        default=None,
        help="output path (default: <path>.perfetto.json, or "
        "fleet.perfetto.json next to the first sink in --fleet mode)",
    )
    parser.add_argument(
        "--fleet",
        action="store_true",
        help="merge multiple per-replica sinks into ONE document with "
        "per-replica tracks aligned on a shared wall-clock timeline",
    )
    parser.add_argument(
        "--offsets",
        default=None,
        help="fleet mode: replica wall-clock offsets — inline JSON or a "
        "path to a JSON file mapping label -> offset seconds (the "
        "fleet view's per-replica clock_offset_s)",
    )
    args = parser.parse_args(argv)

    from moeva2_ijcai22_replication_tpu.observability.export import (
        read_jsonl,
        to_chrome_trace,
    )

    if args.fleet:
        from moeva2_ijcai22_replication_tpu.observability.fleetrace import (
            merge_fleet_traces,
        )

        sinks: dict[str, str] = {}
        for spec in args.paths:
            if "=" in spec:
                label, path = spec.split("=", 1)
            else:
                label, path = _label_for(spec), spec
            sinks[label] = path
        offsets = None
        if args.offsets:
            if os.path.exists(args.offsets):
                with open(args.offsets) as fh:
                    offsets = json.load(fh)
            else:
                offsets = json.loads(args.offsets)
        out = args.out or os.path.join(
            os.path.dirname(os.path.abspath(args.paths[0].split("=", 1)[-1])),
            "fleet.perfetto.json",
        )
        doc = merge_fleet_traces(sinks, offsets, out_path=out)
        report = doc["otherData"]["fleet_merge"]
        for label, info in sorted(report["replicas"].items()):
            print(
                f"  {label}: {info['events']} events, offset "
                f"{info['offset_s']}s, shift {info['shift_s']}s"
            )
        for label, why in sorted(report["skipped"].items()):
            print(f"  {label}: SKIPPED ({why})", file=sys.stderr)
        if not report["replicas"]:
            print("warning: no sink contributed events", file=sys.stderr)
        print(
            f"{len(report['replicas'])} replica sinks -> "
            f"{len(doc['traceEvents'])} trace-event records -> {out}"
        )
        return 0

    if len(args.paths) != 1:
        parser.error("multiple sinks need --fleet (single-sink mode merges nothing)")
    path = args.paths[0]
    events = read_jsonl(path)
    doc = to_chrome_trace(events)
    out = args.out or f"{path}.perfetto.json"
    with open(out, "w") as fh:
        json.dump(doc, fh)
    if not events:
        # an empty or fully-truncated sink still yields a valid (empty)
        # Perfetto document — warn instead of stack-tracing
        print(f"warning: {path} contained no parseable trace events",
              file=sys.stderr)
    print(
        f"{len(events)} trace events -> {len(doc['traceEvents'])} "
        f"trace-event records -> {out}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
