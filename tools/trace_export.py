#!/usr/bin/env python
"""Render a trace JSONL sink to Chrome/Perfetto trace-event JSON.

The unified tracing subsystem (``moeva2_ijcai22_replication_tpu/observability``)
appends one JSON event per line to the path configured as
``system.trace_log`` (runners/grids) or ``serving.trace_log`` (the HTTP
front). This CLI converts that stream to the trace-event format the
Perfetto UI (https://ui.perfetto.dev) and ``chrome://tracing`` open
directly — one process track per trace id (request/run/batch), "X" slices
for spans, instants for progress events (MoEvA gates), counter tracks for
gauges (writer queue depth).

    python tools/trace_export.py out/trace.jsonl
    python tools/trace_export.py out/trace.jsonl -o trace.perfetto.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("path", help="trace JSONL file (system.trace_log)")
    parser.add_argument(
        "-o",
        "--out",
        default=None,
        help="output path (default: <path>.perfetto.json)",
    )
    args = parser.parse_args(argv)

    from moeva2_ijcai22_replication_tpu.observability.export import (
        read_jsonl,
        to_chrome_trace,
    )

    events = read_jsonl(args.path)
    doc = to_chrome_trace(events)
    out = args.out or f"{args.path}.perfetto.json"
    with open(out, "w") as fh:
        json.dump(doc, fh)
    if not events:
        # an empty or fully-truncated sink still yields a valid (empty)
        # Perfetto document — warn instead of stack-tracing
        print(f"warning: {args.path} contained no parseable trace events",
              file=sys.stderr)
    print(
        f"{len(events)} trace events -> {len(doc['traceEvents'])} "
        f"trace-event records -> {out}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
