"""Probe whether the Pallas association kernel is safe for YOUR attack program.

The MoEvA Pallas niche-association kernel is ~15% faster end-to-end but some
compiled configurations fault the TPU *worker process* (engine ``use_pallas``
docstring). The fault is a property of the COMPILED PROGRAM, not the shape
alone — state count AND scan length both matter (537 LCLD states passes at
n_gen=5, faults at n_gen=50) — so the engine defaults to the XLA path and
Pallas is opt-in per validated configuration. This tool does the validation:
it compiles and runs the attack program you describe **in a subprocess**, so
a kernel fault kills the probe child, never your session's backend.

Probe the program you will actually run: same domain, states, pop,
offsprings, n_gen, archive size, and history segmenting.

    python tools/validate_pallas.py --states 537 --n-pop 200 --n-gen 50
    -> UNSAFE: Pallas faulted ... keep use_pallas off
    python tools/validate_pallas.py --states 1000 --n-pop 100 --n-gen 1000
    -> SAFE: validated; opt in with use_pallas=True for this program
    python tools/validate_pallas.py --domain botnet-real --n-pop 200 \
        --archive-size 24 --n-gen 100     # bench.py's botnet program

Exit code: 0 = safe, 1 = Pallas fault, 2 = probe could not run (setup
failed before the kernel was involved — wrong paths, no TPU, ...).
"""

import argparse
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_CHILD = "VALIDATE_PALLAS_CHILD"
_SENTINEL_SETUP = "probe-setup-done"
_SENTINEL_OK = "probe-ok"


def _probe(args) -> None:
    """Child body: build the requested program and run it with Pallas on."""
    import numpy as np

    from moeva2_ijcai22_replication_tpu.attacks.moeva import Moeva2
    from moeva2_ijcai22_replication_tpu.models.io import (
        Surrogate, load_classifier,
    )
    from moeva2_ijcai22_replication_tpu.models.mlp import init_params, lcld_mlp
    from moeva2_ijcai22_replication_tpu.models.scalers import (
        fit_minmax, load_joblib_scaler,
    )

    ref = "/root/reference"
    if args.domain == "botnet-real":
        from moeva2_ijcai22_replication_tpu.domains.botnet import BotnetConstraints

        cons = BotnetConstraints(
            f"{ref}/data/botnet/features.csv", f"{ref}/data/botnet/constraints.csv"
        )
        x = np.load(f"{ref}/data/botnet/x_candidates_common.npy")
        if args.states:
            x = x[: args.states]
        sur = load_classifier(f"{ref}/models/botnet/nn.model")
        scaler = load_joblib_scaler(f"{ref}/models/botnet/scaler.joblib")
    else:
        from moeva2_ijcai22_replication_tpu.domains.lcld import LcldConstraints
        from moeva2_ijcai22_replication_tpu.domains.synth import synth_lcld

        cons = LcldConstraints(
            f"{ref}/data/lcld/features.csv", f"{ref}/data/lcld/constraints.csv"
        )
        x = synth_lcld(args.states or 1000, cons.schema, seed=0)
        model = lcld_mlp()
        sur = Surrogate(model, init_params(model, cons.schema.n_features, seed=0))
        scaler = fit_minmax(x.min(0), x.max(0))

    moeva = Moeva2(
        classifier=sur,
        constraints=cons,
        ml_scaler=scaler,
        norm=2,
        n_gen=args.n_gen,
        n_pop=args.n_pop,
        n_offsprings=args.n_offsprings,
        archive_size=args.archive_size,
        save_history=args.save_history or None,
        history_chunk=args.history_chunk,
        seed=0,
        use_pallas=True,
    )
    # everything below this line involves the Pallas-enabled program; a
    # death before the sentinel is a setup problem, not a kernel fault
    print(_SENTINEL_SETUP, flush=True)
    res = moeva.generate(x, 1)
    assert np.isfinite(res.f).all()
    print(_SENTINEL_OK)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--domain", choices=["lcld-synth", "botnet-real"],
                    default="lcld-synth")
    ap.add_argument("--states", type=int, default=0,
                    help="0 = domain default (1000 synth / all 387 botnet)")
    ap.add_argument("--n-pop", type=int, default=100)
    ap.add_argument("--n-offsprings", type=int, default=100)
    ap.add_argument("--n-gen", type=int, default=50)
    ap.add_argument("--archive-size", type=int, default=0)
    ap.add_argument("--save-history", choices=["reduced", "full"], default=None)
    ap.add_argument("--history-chunk", type=int, default=50,
                    help="segment length when history is recorded — it sets "
                         "the compiled scan length, which the fault depends on")
    args = ap.parse_args()

    if os.environ.get(_CHILD):
        _probe(args)
        return 0

    env = dict(os.environ, **{_CHILD: "1"})
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)] + sys.argv[1:],
            env=env, capture_output=True, text=True,
            # compile (~40s) + generous run budget; a wedged (not crashed)
            # worker must not hang the validator forever
            timeout=300 + 0.2 * args.n_gen,
        )
        out, err, rc = proc.stdout, proc.stderr, proc.returncode
        timed_out = False
    except subprocess.TimeoutExpired as e:
        out = (e.stdout or b"").decode() if isinstance(e.stdout, bytes) else (e.stdout or "")
        err = (e.stderr or b"").decode() if isinstance(e.stderr, bytes) else (e.stderr or "")
        rc, timed_out = -1, True

    prog = (f"({args.domain}, {args.states or 'default'} states, "
            f"pop {args.n_pop}, n_gen {args.n_gen}, "
            f"archive {args.archive_size}, history {args.save_history})")
    if rc == 0 and _SENTINEL_OK in out:
        print(f"SAFE: validated; opt in with use_pallas=True for {prog}")
        return 0
    if _SENTINEL_SETUP in out:
        verdict = "hung" if timed_out else "faulted"
        print(f"UNSAFE: Pallas-enabled program {verdict} at {prog} — keep use_pallas off")
        for line in (err or out).strip().splitlines()[-1:]:
            print(f"  last output: {line[:120]}")
        return 1
    print(f"probe could not run (setup failed before the kernel was involved) at {prog}")
    for line in (err or out).strip().splitlines()[-1:]:
        print(f"  last output: {line[:120]}")
    return 2


if __name__ == "__main__":
    sys.exit(main())
